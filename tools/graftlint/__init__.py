"""graftlint: project-invariant static analysis for the serving stack.

``make lint`` (ruff + compileall) catches syntax rot and style; it knows
nothing about the invariants PRs 1-5 established — zero per-step
host-to-device transfers in the decode loop, engine-thread-only state
snapshotted before crossing to HTTP handlers, paired page alloc/free
with refcount discipline. A regression in any of those surfaces only as
a flaky stress test or a silent perf cliff. graftlint encodes them as
AST checkers that run over the whole tree in ``make analyze``.

Layout:

- :mod:`tools.graftlint.core` — the framework: project loader,
  annotation/suppression comment parsing, the ``Checker`` protocol,
  baseline matching, human + JSON reporting.
- :mod:`tools.graftlint.checkers` — the per-invariant plugins (one
  module per rule; the registry is ``ALL_CHECKERS``).
- ``tools/graftlint/baseline.json`` — grandfathered violations, each
  with a written justification. ``GRAFTLINT_STRICT=1`` additionally
  refuses a stale baseline (entries that no longer fire).

Source annotations the checkers read (plain comments, zero runtime
cost):

- ``# graftlint: hot-path`` on a ``def`` line registers the function as
  a decode-loop hot path (the hot-path-h2d checker's scope).
- ``# owner: engine`` on a ``self.x = ...`` line declares the attribute
  engine-thread-only (the thread-ownership checker's scope).
- ``# graftlint: cross-thread`` on a ``def`` line marks a non-async
  function that runs off the engine thread (HTTP/event-loop side).
- ``# graftlint: disable=<rule>[,<rule>...]`` suppresses the named
  rule(s) on that line.

Usage::

    python -m tools.graftlint [paths...] [--json] [--strict] [--list]
"""

from tools.graftlint.core import (  # noqa: F401  (the public surface)
    Checker,
    Project,
    Violation,
    load_project,
    run_checkers,
)
