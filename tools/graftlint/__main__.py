"""CLI: ``python -m tools.graftlint [paths...]``.

Exit 0 when every finding is suppressed or baselined; 1 when new
violations exist (or, under ``--strict``/``GRAFTLINT_STRICT=1``, when
the baseline has gone stale — a fixed violation must leave the baseline
with the fix, so the grandfather list only ever shrinks honestly).

The last stdout line is always the one-line JSON summary the CI spine
consumes (the bench-runner convention: one parseable line no matter
what)::

    {"rules": 6, "files": 187, "violations": 0, "baselined": 1}
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.graftlint.checkers import ALL_CHECKERS
from tools.graftlint.core import load_baseline, load_project, run_checkers

DEFAULT_PATHS = ["k8s_gpu_device_plugin_tpu", "tests", "tools", "bench.py"]
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="project-invariant static analysis (see "
                    "docs/static_analysis.md)",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help=f"files/dirs to analyze (default: "
                             f"{' '.join(DEFAULT_PATHS)})")
    parser.add_argument("--json", action="store_true",
                        help="emit full machine-readable findings "
                             "instead of human lines")
    parser.add_argument("--strict", action="store_true",
                        help="also fail on a stale baseline (entries "
                             "that no longer fire); GRAFTLINT_STRICT=1 "
                             "implies this")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline file (default: the checked-in "
                             "tools/graftlint/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (every violation is "
                             "new); what the fixture tests use")
    parser.add_argument("--list", action="store_true",
                        help="list the registered rules and exit")
    args = parser.parse_args(argv)

    if args.list:
        for c in ALL_CHECKERS:
            print(f"{c.name}: {c.description}")
        return 0

    strict = args.strict or os.environ.get("GRAFTLINT_STRICT") == "1"
    paths = args.paths or DEFAULT_PATHS
    # a typo'd path must ERROR, not silently shrink the analyzed set —
    # CI reporting violations:0 over the subset it happened to find
    # would read as "covered everything"
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"graftlint: no such path(s): {' '.join(missing)}",
              file=sys.stderr)
        return 2

    try:
        baseline = {} if args.no_baseline else load_baseline(args.baseline)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"graftlint: bad baseline: {e}", file=sys.stderr)
        return 2

    project = load_project(paths)
    new, baselined, stale = run_checkers(project, ALL_CHECKERS, baseline)

    summary = {
        "rules": len(ALL_CHECKERS),
        "files": len(project.modules) + len(project.parse_errors),
        "violations": len(new),
        "baselined": len(baselined),
    }
    if stale:
        summary["stale_baseline"] = len(stale)

    if args.json:
        print(json.dumps({
            "summary": summary,
            "violations": [v.__dict__ for v in new],
            "baselined": [v.__dict__ for v in baselined],
            "stale": stale,
        }, indent=2))
    else:
        for v in new:
            print(v.render())
        if stale and strict:
            for e in stale:
                print(
                    f"stale baseline entry [{e.get('rule')}] "
                    f"{e.get('path')} ({e.get('symbol')}/{e.get('key')}): "
                    "no longer fires — remove it"
                )
        print(json.dumps(summary))

    if new:
        return 1
    if strict and stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
