#!/usr/bin/env python3
"""Hardware-window harvester: run the whole PERF.md measurement queue
with one command the moment the tunneled chip comes alive.

Windows are scarce (rounds 3-4 lost multi-hour stretches to a wedged
tunnel), so when one opens nothing should be improvised: this runs every
queued workload in priority order — headline numbers first, tuning sweeps
after — with per-workload timeouts, appends each result to
``harvest_results.jsonl`` the moment it lands (a mid-run wedge loses
nothing), and re-probes the chip after any failure so a dead tunnel stops
the run instead of eating the queue's budget.

Child spawning is bench.py's (same cwd/PYTHONPATH/platform-cycling
caveats, one implementation): importing the driver's own helpers keeps
the two harvesting paths from diverging.

Usage:
    python tools/harvest.py                # full queue
    python tools/harvest.py train decode   # just these workloads

Never run concurrently with bench.py — libtpu is single-client.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import bench  # noqa: E402  (the driver entry point doubles as a library)

RESULTS_PATH = os.path.join(REPO_ROOT, "harvest_results.jsonl")
PROBE_TIMEOUT = 60.0
TPU_PLATFORMS = (None, "tpu", "")  # same fallback cycle as bench.py

# (row name, runner workload, timeout_seconds) in harvest-priority order.
# Round-5 ordering (VERDICT r4 #1): the train headline is BANKED in the
# journal (55.13% MFU, 03:46Z window) while the entire serving stack has
# zero hardware numbers after two rounds — so never-measured rows lead and
# banked-metric refreshes trail. The observed window length is ~12-15
# minutes; the first ~4 rows are what a short window actually buys.
# Row names are what the CLI filter and the journal use; the distinct
# "train_tuned" row re-times the SAME train workload with flash_tune's
# persisted winners (.flash_tilings.json from the last sweep) resolved,
# measuring the tuned payoff against the banked baseline row.
QUEUE: list[tuple[str, str, float]] = [
    ("decode", "decode", 420),        # serving economics headline, never on hw
    ("usage_live", "usage_live", 120),  # reader vs the real runtime (cheap)
    ("serve", "serve", 600),          # continuous-batching request throughput
    ("train_tuned", "train", 480),    # flash_tune winners' payoff (55->83 lever)
    ("decode_int8w", "decode_int8w", 420),  # weight-quant HBM lever
    ("decode_ragged", "decode_ragged", 420),  # Pallas ragged decode kernel
    ("decode_int8kv", "decode_int8kv", 420),  # cache-quant lever isolated
    ("decode_int4w", "decode_int4w", 420),
    ("decode_lora", "decode_lora", 420),  # multi-LoRA serving overhead
    ("breakdown", "breakdown", 600),  # step-time attribution (55->83 map)
    ("breakdown_attn", "breakdown_attn", 600),
    ("remat_tune", "remat_tune", 900),  # HBM-vs-recompute dial, 4 variants
    ("train_int8", "train_int8", 480),          # MXU double-rate path
    ("train_fusedopt", "train_fusedopt", 480),  # fused AdamW
    ("opt_tune", "opt_tune", 600),
    ("train_bs16", "train_bs16", 480),  # double batch: overhead amortization
    # Banked-metric refreshes: fresh journal rows make --resume skip these;
    # they re-measure only once the never-measured rows above have landed
    # or the banked values have aged out (48h bound shared with bench.py).
    ("matmul", "matmul", 300),        # 83% ceiling check (BASELINE #2)
    ("train", "train", 480),          # headline: train MFU vs 55.13 record
    ("allocated", "allocated", 600),  # n=4096 parity through Allocate
    ("flash_tune", "flash_tune", 900),  # backward tilings sweep
    ("flash_tune_long", "flash_tune_long", 1200),  # S=8192, expendable
]

# Repeat/variance discipline (VERDICT r4 weak #2: single best-of-N rows
# made the 83.06->80.72 matmul drift uninterpretable). A row repeats its
# workload inside its OWN timeout budget — never costing the queue more
# than the single-run design did — and journals every repeat plus the
# spread; ``result`` stays the median repeat so bench.py's adoption picks
# a central value with no format change. Sweeps and one-shot validations
# are excluded (a sweep's own grid is its variance story).
MAX_REPEATS = 3
REPEAT_MARGIN = 20.0  # seconds of slack a repeat must leave in the budget
NO_REPEAT = {"flash_tune", "flash_tune_long", "remat_tune", "opt_tune",
             "usage_live", "breakdown", "breakdown_attn"}
# Primary metric per workload family, used to order repeats for the median
# and to express the spread; first key present in the result wins.
PRIMARY_KEYS = ("mfu_pct", "decode_tokens_per_second", "requests_per_second",
                "tokens_per_second", "scrapes_with_data")


def primary_key(result: dict) -> str | None:
    for k in PRIMARY_KEYS:
        if isinstance(result.get(k), (int, float)):
            return k
    return None


def median_of(repeats: list[dict]) -> tuple[dict, dict | None]:
    """(median repeat, spread summary) — lower-middle for even n so the
    reported dict is always a really-measured run, never an interpolation."""
    key = primary_key(repeats[0])
    if key is None or len(repeats) == 1:
        return repeats[0], None
    ordered = sorted(repeats, key=lambda r: r[key])
    med = ordered[(len(ordered) - 1) // 2]
    vals = [r[key] for r in repeats]
    lo, hi = min(vals), max(vals)
    center = med[key] if med[key] else 1.0
    return med, {
        "metric": key,
        "values": vals,
        "rel_spread_pct": round(100.0 * (hi - lo) / abs(center), 2),
    }

_T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"harvest [{time.monotonic() - _T0:7.1f}s] {msg}", flush=True)


def run_child(workload: str, timeout: float, attempt: int = 0) -> dict | None:
    """One runner child via bench.py's spawner; None on timeout/garbage."""
    plat = TPU_PLATFORMS[attempt % len(TPU_PLATFORMS)]
    try:
        return bench._run_child(workload, timeout=timeout, platforms=plat)
    except subprocess.TimeoutExpired:
        log(f"{workload}: TIMED OUT after {timeout:.0f}s")
    except Exception as e:  # noqa: BLE001 - the queue must survive any child
        log(f"{workload}: {type(e).__name__}: {e}")
    return None


def persist(workload: str, result: dict | None,
            repeats: list[dict] | None = None) -> dict:
    """Append one journal row; returns the record so callers can log the
    spread that was actually written (computed exactly once, here)."""
    rec: dict = {
        "workload": workload,
        "t": round(time.monotonic() - _T0, 1),
        "ts": round(time.time(), 1),  # bench.py's fallback ages by this
    }
    if repeats and len(repeats) > 1:
        med, spread = median_of(repeats)
        rec["result"] = med  # adoption (bench.py) reads this: the median
        rec["n_repeats"] = len(repeats)
        rec["repeats"] = repeats
        if spread is not None:
            rec["spread"] = spread
    else:
        rec["result"] = result
        if repeats:
            rec["n_repeats"] = 1
    try:
        with open(RESULTS_PATH, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError as e:  # journaling must never kill the run
        log(f"persist failed: {e}")
    return rec


def landed_rows() -> set[str]:
    """Row names with a successful, still-fresh result in the journal.
    The validity AND freshness predicates are bench.py's — shared, so
    --resume and the driver's adoption fallback can never disagree: a row
    --resume would skip is exactly a row adoption would use."""
    done: set[str] = set()
    try:
        with open(RESULTS_PATH) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if bench.journal_row_ok(rec) and bench.journal_row_fresh(rec):
                    done.add(rec.get("workload", ""))
    except OSError:
        pass
    return done


def _script_pids(script: str) -> list[int]:
    """Pids of live ``python <script>`` processes (argv-exact /proc scan).

    NOT pgrep -f: full-cmdline substring matching false-positives on any
    process whose arguments merely mention the script — including this
    session's own driver wrapper, whose embedded prompt text contains
    both 'python' and 'bench.py' and would make a pgrep-based guard
    refuse every harvest forever."""
    me = (os.getpid(), os.getppid())
    out: list[int] = []
    for d in os.listdir("/proc"):
        if not d.isdigit() or int(d) in me:
            continue
        try:
            with open(f"/proc/{d}/cmdline", "rb") as f:
                argv = f.read().split(b"\0")
        except OSError:
            continue
        if not argv or b"python" not in os.path.basename(argv[0]):
            continue
        # the script must BE an early argument (python [-u/-X ...] script),
        # not a substring of some -c blob or prompt text
        for a in argv[1:4]:
            s = a.decode(errors="replace")
            if s == script or s.endswith("/" + script):
                out.append(int(d))
                break
    return out


def _proc_start_ticks(pid: int) -> int:
    """Kernel start time (clock ticks since boot; /proc/<pid>/stat field
    22). Unreadable (gone/raced) reads as newest-possible so a vanished
    process never outranks a live one."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return int(f.read().rsplit(") ", 1)[1].split()[19])
    except Exception:  # noqa: BLE001
        return 1 << 62


def bench_running() -> bool:
    """The driver's bench.py owns the chip unconditionally: its
    end-of-round artifact must never lose the window to a harvest."""
    return bool(_script_pids("bench.py"))


def script_outranked(script: str) -> bool:
    """True if an OLDER instance of ``script`` is already running
    (start-time tie-break, pid as the tiebreaker of last resort): exactly
    one of two racing starts proceeds — no mutual refusal livelock — and
    a running instance is never evicted by a newcomer (the newcomer is
    the one that backs off). Shared by harvest.py and watchdog.py so the
    priority rule can never diverge between them."""
    me = os.getpid()
    mine = (_proc_start_ticks(me), me)
    return any(
        (_proc_start_ticks(pid), pid) < mine
        for pid in _script_pids(script)
    )


def harvest_outranked() -> bool:
    return script_outranked("harvest.py")


def _archive_tilings() -> None:
    from k8s_gpu_device_plugin_tpu.ops.flash_attention import (
        tuning_file_path,
    )

    tf = tuning_file_path()
    if os.path.exists(tf):
        try:
            os.replace(tf, tf + ".bak")
            log(f"archived stale tilings {tf} -> .bak (sweep will remeasure)")
        except OSError as e:
            log(f"could not archive {tf}: {e}")


def probe(attempt: int = 0) -> bool:
    result = run_child("probe", PROBE_TIMEOUT, attempt)
    # a runner child reports failures as {"error": ...} with rc!=0 — a
    # CPU-only fallback or a dead tunnel must read as NOT live
    return result is not None and "error" not in result


def main() -> int:
    argv = sys.argv[1:]
    resume = "--resume" in argv
    only = [a for a in argv if a != "--resume"]
    known = {name for name, _, _ in QUEUE}
    unknown = [w for w in only if w not in known]
    if unknown:
        # a typo must not silently skip the queue's headline measurements
        print(f"unknown row(s) {unknown}; queue: {sorted(known)}",
              file=sys.stderr)
        return 2
    queue = [row for row in QUEUE if not only or row[0] in only]
    if resume:
        done_rows = landed_rows()
        queue = [row for row in queue if row[0] not in done_rows]
        if not queue:
            log("--resume: every queued row already landed; nothing to do")
            return 3  # distinct rc so a watchdog loop knows to stop
    if bench_running():
        log("bench.py is running (single-client chip) — refusing to start")
        return 4
    if harvest_outranked():
        log("an older harvest.py is already running — refusing to start")
        return 4

    log(f"probing chip (queue: {[name for name, _, _ in queue]})")
    # remember WHICH platform fallback answered: workloads and retries run
    # on the platform the chip actually speaks, not a fixed guess
    live_attempt = next((i for i in range(3) if probe(i)), None)
    if live_attempt is None:
        log("chip is NOT live — aborting before the queue")
        return 1
    log(f"chip live (platform fallback #{live_attempt}); harvesting")

    done = 0
    archived = False
    wedged = False
    for name, workload, timeout in queue:
        if bench_running():
            log("bench.py started mid-harvest — yielding the chip to it")
            wedged = True  # not literally wedged, but same rc: back off
            break
        if workload == "flash_tune" and not archived:
            # Archive stale tilings RIGHT BEFORE the sweep replaces them
            # (not at startup — a dead probe or an earlier-row wedge must
            # not strand the previous window's winners in the .bak).
            # train_tuned runs EARLIER in the queue against the persisted
            # winners of the LAST sweep; the banked baseline train row is
            # the honest comparison point. flash_tune_long later only
            # MERGES its seq entries and must not wipe the fresh winners.
            archived = True
            _archive_tilings()
        log(f"=== {name} (timeout {timeout:.0f}s) ===")
        t_row = time.monotonic()
        result = run_child(workload, timeout, attempt=live_attempt)
        if result is not None and "error" in result:
            log(f"{name}: runner error: {result['error']}")
        if result is not None and "error" not in result:
            # journal the first landing IMMEDIATELY — a kill/wedge during a
            # repeat must not lose an already-measured scarce-window result
            persist(name, result, repeats=[result])
            repeats = [result]
            first_elapsed = time.monotonic() - t_row
            k0 = primary_key(result)
            repeat_timed_out = False
            # Repeats ride the SAME row budget: a repeat only launches if
            # the budget can still cover a run the size of the first one
            # (later runs are cheaper — the XLA compile cache is warm), so
            # variance never costs a later row its window share.
            while (workload not in NO_REPEAT
                   and len(repeats) < MAX_REPEATS
                   and k0 is not None):
                remaining = timeout - (time.monotonic() - t_row)
                if remaining < first_elapsed + REPEAT_MARGIN:
                    break
                r = run_child(workload, remaining, attempt=live_attempt)
                if r is None or "error" in r:
                    # a TIMED-OUT repeat smells like a wedge; re-probe below
                    repeat_timed_out = r is None
                    log(f"{name}: repeat {len(repeats) + 1} failed; "
                        "keeping the measured ones")
                    break
                if not isinstance(r.get(k0), (int, float)):
                    # a repeat missing the first run's primary metric can't
                    # be ordered for the median — drop it, keep the rest
                    log(f"{name}: repeat {len(repeats) + 1} lacks {k0!r}; "
                        "dropped")
                    break
                repeats.append(r)
            if len(repeats) > 1:
                # the first run was journaled the moment it landed; this
                # consolidated row comes LATER in the file, so readers that
                # take the last row per workload adopt the median
                rec = persist(name, result, repeats=repeats)
                log(f"{name}: OK x{len(repeats)} spread="
                    f"{json.dumps(rec.get('spread'))}")
            else:
                log(f"{name}: OK {json.dumps(result)[:300]}")
            done += 1
            if repeat_timed_out:
                # mirror the first-run failure path: a dead chip must stop
                # the queue here, not after the NEXT row burns its timeout
                found = next((i for i in range(3) if probe(i)), None)
                if found is None:
                    log("chip wedged during a repeat — stopping "
                        "(results are journaled)")
                    wedged = True
                    break
                live_attempt = found
            continue
        persist(name, result)
        # failure: one retry if the chip still answers, else stop the run.
        # The re-probe cycles every platform fallback and the retry uses
        # whichever one answered — a pinned-name flake must not abandon
        # (or silently mis-retry) the rest of the window.
        found = next((i for i in range(3) if probe(i)), None)
        if found is None:
            log("chip wedged mid-harvest — stopping (results are journaled)")
            wedged = True
            break
        live_attempt = found
        log(f"{name}: chip still live (fallback #{found}), one retry")
        result = run_child(workload, timeout, attempt=live_attempt)
        persist(name, result)
        if result is not None and "error" not in result:
            done += 1
            log(f"{name}: OK on retry")
        else:
            log(f"{name}: failed twice with a live chip; moving on")

    log(f"harvest complete: {done}/{len(queue)} workloads -> {RESULTS_PATH}")
    # rc 0 is a "window may still be open, rows landed" signal a watchdog
    # re-enters on immediately; a wedge-break or a zero-progress pass must
    # read as rc 1 (back off and probe later) instead.
    return 0 if done > 0 and not wedged else 1


if __name__ == "__main__":
    sys.exit(main())
