#!/usr/bin/env python3
"""Hardware-window harvester: run the whole PERF.md measurement queue
with one command the moment the tunneled chip comes alive.

Windows are scarce (rounds 3-4 lost multi-hour stretches to a wedged
tunnel), so when one opens nothing should be improvised: this runs every
queued workload in priority order — headline numbers first, tuning sweeps
after — with per-workload timeouts, appends each result to
``harvest_results.jsonl`` the moment it lands (a mid-run wedge loses
nothing), and re-probes the chip after any failure so a dead tunnel stops
the run instead of eating the queue's budget.

Child spawning is bench.py's (same cwd/PYTHONPATH/platform-cycling
caveats, one implementation): importing the driver's own helpers keeps
the two harvesting paths from diverging.

Usage:
    python tools/harvest.py                # full queue
    python tools/harvest.py train decode   # just these workloads

Never run concurrently with bench.py — libtpu is single-client.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import bench  # noqa: E402  (the driver entry point doubles as a library)

RESULTS_PATH = os.path.join(REPO_ROOT, "harvest_results.jsonl")
PROBE_TIMEOUT = 60.0
TPU_PLATFORMS = (None, "tpu", "")  # same fallback cycle as bench.py

# (workload, timeout_seconds) in harvest-priority order: headline metrics
# first (train MFU is the driver-recorded number), then the Allocate-path
# parity proof, the tuning sweeps that order the next optimization, the
# serving-side economics, and the live-runtime metrics validation.
QUEUE: list[tuple[str, float]] = [
    ("matmul", 300),          # 83% ceiling confirmation (BASELINE #2)
    ("train", 480),           # the headline: train MFU vs 54.65 record
    ("allocated", 600),       # n=4096 parity through Allocate (verdict #2)
    ("flash_tune", 900),      # backward flash tilings (the 55->83 lever)
    # train again AFTER the sweep: flash_tune persists its winners to the
    # tilings file and flash_attention resolves them automatically, so
    # this row measures the tuned payoff against the baseline train row
    ("train", 480),
    ("breakdown", 600),       # step-time attribution orders the levers
    ("breakdown_attn", 600),
    ("train_fusedopt", 480),  # fused AdamW: may carry the primary
    ("train_int8", 480),      # MXU double-rate path
    ("opt_tune", 600),
    ("decode", 420),          # serving economics, never hardware-measured
    ("decode_int8w", 420),
    ("decode_int4w", 420),
    ("serve", 600),
    ("usage_live", 120),      # LibtpuUsageReader vs the real runtime
    ("flash_tune_long", 1200),  # S=8192 tilings, most expendable
]

_T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"harvest [{time.monotonic() - _T0:7.1f}s] {msg}", flush=True)


def run_child(workload: str, timeout: float, attempt: int = 0) -> dict | None:
    """One runner child via bench.py's spawner; None on timeout/garbage."""
    plat = TPU_PLATFORMS[attempt % len(TPU_PLATFORMS)]
    try:
        return bench._run_child(workload, timeout=timeout, platforms=plat)
    except subprocess.TimeoutExpired:
        log(f"{workload}: TIMED OUT after {timeout:.0f}s")
    except Exception as e:  # noqa: BLE001 - the queue must survive any child
        log(f"{workload}: {type(e).__name__}: {e}")
    return None


def persist(workload: str, result: dict | None) -> None:
    try:
        with open(RESULTS_PATH, "a") as f:
            f.write(json.dumps({
                "workload": workload,
                "t": round(time.monotonic() - _T0, 1),
                "result": result,
            }) + "\n")
    except OSError as e:  # journaling must never kill the run
        log(f"persist failed: {e}")


def probe(attempt: int = 0) -> bool:
    result = run_child("probe", PROBE_TIMEOUT, attempt)
    # a runner child reports failures as {"error": ...} with rc!=0 — a
    # CPU-only fallback or a dead tunnel must read as NOT live
    return result is not None and "error" not in result


def main() -> int:
    only = sys.argv[1:]
    known = {w for w, _ in QUEUE}
    unknown = [w for w in only if w not in known]
    if unknown:
        # a typo must not silently skip the queue's headline measurements
        print(f"unknown workload(s) {unknown}; queue: {sorted(known)}",
              file=sys.stderr)
        return 2
    if only:
        # dedupe by name: QUEUE's repeated train row only means something
        # with flash_tune in the same invocation; a name filter must not
        # burn 2x480s on two indistinguishable rows
        seen: set[str] = set()
        queue = [
            (w, t) for w, t in QUEUE
            if w in only and (w not in seen and not seen.add(w))
        ]
    else:
        queue = list(QUEUE)

    if any(w == "flash_tune" for w, _ in queue):
        # A sweep will re-measure tilings: archive any stale file so the
        # BASELINE train row runs on defaults (otherwise the tuned-vs-
        # baseline comparison silently measures tuned-vs-tuned), while the
        # .bak preserves the previous window's winners.
        from k8s_gpu_device_plugin_tpu.ops.flash_attention import (
            tuning_file_path,
        )

        tf = tuning_file_path()
        if os.path.exists(tf):
            try:
                os.replace(tf, tf + ".bak")
                log(f"archived stale tilings {tf} -> .bak (fresh sweep queued)")
            except OSError as e:
                log(f"could not archive {tf}: {e}")

    log(f"probing chip (queue: {[w for w, _ in queue]})")
    # remember WHICH platform fallback answered: workloads and retries run
    # on the platform the chip actually speaks, not a fixed guess
    live_attempt = next((i for i in range(3) if probe(i)), None)
    if live_attempt is None:
        log("chip is NOT live — aborting before the queue")
        return 1
    log(f"chip live (platform fallback #{live_attempt}); harvesting")

    done = 0
    for workload, timeout in queue:
        log(f"=== {workload} (timeout {timeout:.0f}s) ===")
        result = run_child(workload, timeout, attempt=live_attempt)
        if result is not None and "error" in result:
            log(f"{workload}: runner error: {result['error']}")
        persist(workload, result)
        if result is not None and "error" not in result:
            done += 1
            log(f"{workload}: OK {json.dumps(result)[:300]}")
            continue
        # failure: one retry if the chip still answers, else stop the run.
        # The re-probe cycles every platform fallback and the retry uses
        # whichever one answered — a pinned-name flake must not abandon
        # (or silently mis-retry) the rest of the window.
        found = next((i for i in range(3) if probe(i)), None)
        if found is None:
            log("chip wedged mid-harvest — stopping (results are journaled)")
            break
        live_attempt = found
        log(f"{workload}: chip still live (fallback #{found}), one retry")
        result = run_child(workload, timeout, attempt=live_attempt)
        persist(workload, result)
        if result is not None and "error" not in result:
            done += 1
            log(f"{workload}: OK on retry")
        else:
            log(f"{workload}: failed twice with a live chip; moving on")

    log(f"harvest complete: {done}/{len(queue)} workloads -> {RESULTS_PATH}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
