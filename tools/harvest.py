#!/usr/bin/env python3
"""Hardware-window harvester: run the whole PERF.md measurement queue
with one command the moment the tunneled chip comes alive.

Windows are scarce (rounds 3-4 lost multi-hour stretches to a wedged
tunnel), so when one opens nothing should be improvised: this runs every
queued workload in priority order — headline numbers first, tuning sweeps
after — with per-workload timeouts, appends each result to
``harvest_results.jsonl`` the moment it lands (a mid-run wedge loses
nothing), and re-probes the chip after any failure so a dead tunnel stops
the run instead of eating the queue's budget.

Child spawning is bench.py's (same cwd/PYTHONPATH/platform-cycling
caveats, one implementation): importing the driver's own helpers keeps
the two harvesting paths from diverging.

Usage:
    python tools/harvest.py                # full queue
    python tools/harvest.py train decode   # just these workloads

Never run concurrently with bench.py — libtpu is single-client.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import bench  # noqa: E402  (the driver entry point doubles as a library)

RESULTS_PATH = os.path.join(REPO_ROOT, "harvest_results.jsonl")
PROBE_TIMEOUT = 60.0
TPU_PLATFORMS = (None, "tpu", "")  # same fallback cycle as bench.py

# (row name, runner workload, timeout_seconds) in harvest-priority order:
# headline metrics first (train MFU is the driver-recorded number), then
# the Allocate-path parity proof, the tuning sweeps that order the next
# optimization, the serving-side economics, and the live-runtime metrics
# validation. Row names are what the CLI filter and the journal use; the
# distinct "train_tuned" row re-times the SAME train workload after
# flash_tune persisted its winners, measuring the tuned payoff against
# the baseline row.
QUEUE: list[tuple[str, str, float]] = [
    ("matmul", "matmul", 300),        # 83% ceiling check (BASELINE #2)
    ("train", "train", 480),          # headline: train MFU vs 54.65 record
    ("allocated", "allocated", 600),  # n=4096 parity through Allocate
    ("flash_tune", "flash_tune", 900),  # backward tilings (55->83 lever)
    ("train_tuned", "train", 480),    # tuned payoff vs the baseline row
    ("breakdown", "breakdown", 600),  # step-time attribution
    ("breakdown_attn", "breakdown_attn", 600),
    ("train_fusedopt", "train_fusedopt", 480),  # fused AdamW
    ("train_int8", "train_int8", 480),          # MXU double-rate path
    ("opt_tune", "opt_tune", 600),
    ("remat_tune", "remat_tune", 900),  # HBM-vs-recompute dial, 4 variants
    ("train_bs16", "train_bs16", 480),  # double batch: overhead amortization
    ("decode", "decode", 420),        # serving economics, never on hw
    ("decode_int8w", "decode_int8w", 420),
    ("decode_int4w", "decode_int4w", 420),
    ("decode_int8kv", "decode_int8kv", 420),  # cache-quant lever isolated
    ("decode_ragged", "decode_ragged", 420),  # Pallas ragged decode kernel
    ("decode_lora", "decode_lora", 420),  # multi-LoRA serving overhead
    ("serve", "serve", 600),
    ("usage_live", "usage_live", 120),  # reader vs the real runtime
    ("flash_tune_long", "flash_tune_long", 1200),  # S=8192, expendable
]

_T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"harvest [{time.monotonic() - _T0:7.1f}s] {msg}", flush=True)


def run_child(workload: str, timeout: float, attempt: int = 0) -> dict | None:
    """One runner child via bench.py's spawner; None on timeout/garbage."""
    plat = TPU_PLATFORMS[attempt % len(TPU_PLATFORMS)]
    try:
        return bench._run_child(workload, timeout=timeout, platforms=plat)
    except subprocess.TimeoutExpired:
        log(f"{workload}: TIMED OUT after {timeout:.0f}s")
    except Exception as e:  # noqa: BLE001 - the queue must survive any child
        log(f"{workload}: {type(e).__name__}: {e}")
    return None


def persist(workload: str, result: dict | None) -> None:
    try:
        with open(RESULTS_PATH, "a") as f:
            f.write(json.dumps({
                "workload": workload,
                "t": round(time.monotonic() - _T0, 1),
                "ts": round(time.time(), 1),  # bench.py's fallback ages by this
                "result": result,
            }) + "\n")
    except OSError as e:  # journaling must never kill the run
        log(f"persist failed: {e}")


def landed_rows() -> set[str]:
    """Row names with a successful, still-fresh result in the journal.
    The validity AND freshness predicates are bench.py's — shared, so
    --resume and the driver's adoption fallback can never disagree: a row
    --resume would skip is exactly a row adoption would use."""
    done: set[str] = set()
    try:
        with open(RESULTS_PATH) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if bench.journal_row_ok(rec) and bench.journal_row_fresh(rec):
                    done.add(rec.get("workload", ""))
    except OSError:
        pass
    return done


def _script_pids(script: str) -> list[int]:
    """Pids of live ``python <script>`` processes (argv-exact /proc scan).

    NOT pgrep -f: full-cmdline substring matching false-positives on any
    process whose arguments merely mention the script — including this
    session's own driver wrapper, whose embedded prompt text contains
    both 'python' and 'bench.py' and would make a pgrep-based guard
    refuse every harvest forever."""
    me = (os.getpid(), os.getppid())
    out: list[int] = []
    for d in os.listdir("/proc"):
        if not d.isdigit() or int(d) in me:
            continue
        try:
            with open(f"/proc/{d}/cmdline", "rb") as f:
                argv = f.read().split(b"\0")
        except OSError:
            continue
        if not argv or b"python" not in os.path.basename(argv[0]):
            continue
        # the script must BE an early argument (python [-u/-X ...] script),
        # not a substring of some -c blob or prompt text
        for a in argv[1:4]:
            s = a.decode(errors="replace")
            if s == script or s.endswith("/" + script):
                out.append(int(d))
                break
    return out


def _proc_start_ticks(pid: int) -> int:
    """Kernel start time (clock ticks since boot; /proc/<pid>/stat field
    22). Unreadable (gone/raced) reads as newest-possible so a vanished
    process never outranks a live one."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return int(f.read().rsplit(") ", 1)[1].split()[19])
    except Exception:  # noqa: BLE001
        return 1 << 62


def bench_running() -> bool:
    """The driver's bench.py owns the chip unconditionally: its
    end-of-round artifact must never lose the window to a harvest."""
    return bool(_script_pids("bench.py"))


def harvest_outranked() -> bool:
    """True if an OLDER harvest.py is already running (start-time
    tie-break, pid as the tiebreaker of last resort): exactly one of two
    racing starts proceeds — no mutual refusal livelock — and a running
    harvest is never evicted by a newcomer (the newcomer is the one that
    backs off; mid-run checks use bench_running() only)."""
    me = os.getpid()
    mine = (_proc_start_ticks(me), me)
    return any(
        (_proc_start_ticks(pid), pid) < mine
        for pid in _script_pids("harvest.py")
    )


def _archive_tilings() -> None:
    from k8s_gpu_device_plugin_tpu.ops.flash_attention import (
        tuning_file_path,
    )

    tf = tuning_file_path()
    if os.path.exists(tf):
        try:
            os.replace(tf, tf + ".bak")
            log(f"archived stale tilings {tf} -> .bak (sweep will remeasure)")
        except OSError as e:
            log(f"could not archive {tf}: {e}")


def probe(attempt: int = 0) -> bool:
    result = run_child("probe", PROBE_TIMEOUT, attempt)
    # a runner child reports failures as {"error": ...} with rc!=0 — a
    # CPU-only fallback or a dead tunnel must read as NOT live
    return result is not None and "error" not in result


def main() -> int:
    argv = sys.argv[1:]
    resume = "--resume" in argv
    only = [a for a in argv if a != "--resume"]
    known = {name for name, _, _ in QUEUE}
    unknown = [w for w in only if w not in known]
    if unknown:
        # a typo must not silently skip the queue's headline measurements
        print(f"unknown row(s) {unknown}; queue: {sorted(known)}",
              file=sys.stderr)
        return 2
    queue = [row for row in QUEUE if not only or row[0] in only]
    if resume:
        done_rows = landed_rows()
        queue = [row for row in queue if row[0] not in done_rows]
        if not queue:
            log("--resume: every queued row already landed; nothing to do")
            return 3  # distinct rc so a watchdog loop knows to stop
    if bench_running():
        log("bench.py is running (single-client chip) — refusing to start")
        return 4
    if harvest_outranked():
        log("an older harvest.py is already running — refusing to start")
        return 4

    log(f"probing chip (queue: {[name for name, _, _ in queue]})")
    # remember WHICH platform fallback answered: workloads and retries run
    # on the platform the chip actually speaks, not a fixed guess
    live_attempt = next((i for i in range(3) if probe(i)), None)
    if live_attempt is None:
        log("chip is NOT live — aborting before the queue")
        return 1
    log(f"chip live (platform fallback #{live_attempt}); harvesting")

    done = 0
    archived = False
    for name, workload, timeout in queue:
        if bench_running():
            log("bench.py started mid-harvest — yielding the chip to it")
            break
        if workload == "flash_tune" and not archived:
            # Archive stale tilings RIGHT BEFORE the sweep replaces them
            # (not at startup — a dead probe or an earlier-row wedge must
            # not strand the previous window's winners in the .bak). The
            # baseline train row still precedes this in queue order, so
            # tuned-vs-baseline stays honest; flash_tune_long later only
            # MERGES its seq entries and must not wipe the fresh winners.
            archived = True
            _archive_tilings()
        log(f"=== {name} (timeout {timeout:.0f}s) ===")
        result = run_child(workload, timeout, attempt=live_attempt)
        if result is not None and "error" in result:
            log(f"{name}: runner error: {result['error']}")
        persist(name, result)
        if result is not None and "error" not in result:
            done += 1
            log(f"{name}: OK {json.dumps(result)[:300]}")
            continue
        # failure: one retry if the chip still answers, else stop the run.
        # The re-probe cycles every platform fallback and the retry uses
        # whichever one answered — a pinned-name flake must not abandon
        # (or silently mis-retry) the rest of the window.
        found = next((i for i in range(3) if probe(i)), None)
        if found is None:
            log("chip wedged mid-harvest — stopping (results are journaled)")
            break
        live_attempt = found
        log(f"{name}: chip still live (fallback #{found}), one retry")
        result = run_child(workload, timeout, attempt=live_attempt)
        persist(name, result)
        if result is not None and "error" not in result:
            done += 1
            log(f"{name}: OK on retry")
        else:
            log(f"{name}: failed twice with a live chip; moving on")

    log(f"harvest complete: {done}/{len(queue)} workloads -> {RESULTS_PATH}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
