#!/usr/bin/env python3
"""Driver benchmark entry point: ONE JSON line on stdout.

Primary metric (BASELINE config #2): single-chip bf16 matmul MFU on the real
TPU. ``vs_baseline`` is the ratio against the north-star 45% MFU target from
BASELINE.md (the reference publishes no numbers of its own — BASELINE.json
"published": {}).

Extra diagnostics (control-plane round-trip, device info) go to stderr so
stdout stays a single parseable line.
"""

from __future__ import annotations

import json
import sys

NORTH_STAR_MFU = 0.45  # BASELINE.md: >=45% MFU Llama-3-8B on v5p-16


def main() -> int:
    import jax

    from k8s_gpu_device_plugin_tpu.benchmark.workloads.matmul_mfu import matmul_mfu

    device = jax.devices()[0]
    print(
        f"bench: device={device.device_kind!r} backend={jax.default_backend()}",
        file=sys.stderr,
    )

    result = matmul_mfu(n=4096)
    print(
        f"bench: matmul 4096^3 bf16: {result.tflops:.1f} TFLOP/s "
        f"(peak {result.peak_tflops:.0f}, mfu {result.mfu * 100:.1f}%) "
        f"over {result.iters} iters in {result.seconds:.3f}s",
        file=sys.stderr,
    )

    try:
        from k8s_gpu_device_plugin_tpu.benchmark.workloads.train_bench import train_mfu
        from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig

        tcfg = LlamaConfig(
            vocab_size=32000, d_model=2048, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=8192, max_seq=2048,
        )
        tr = train_mfu(tcfg, batch_size=8, seq_len=2048, steps=5, warmup=2)
        print(
            f"bench: llama train (0.6B, S=2048, flash+remat): "
            f"{tr.mfu * 100:.1f}% MFU, {tr.tokens_per_second:.0f} tok/s, "
            f"step {tr.step_seconds * 1000:.0f}ms",
            file=sys.stderr,
        )
    except Exception as e:  # noqa: BLE001 - diagnostics must not kill the line
        print(f"bench: train bench skipped: {type(e).__name__}: {e}", file=sys.stderr)

    try:
        from k8s_gpu_device_plugin_tpu.benchmark.workloads.roundtrip import (
            control_plane_roundtrip,
        )

        rt = control_plane_roundtrip(iters=50)
        print(
            f"bench: control-plane roundtrip: {rt.allocs_per_second:.0f} "
            f"alloc/s, first registration in {rt.first_register_seconds:.2f}s",
            file=sys.stderr,
        )
    except Exception as e:  # noqa: BLE001 - diagnostics must not kill the line
        print(f"bench: roundtrip skipped: {type(e).__name__}: {e}", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "matmul_bf16_mfu",
                "value": round(result.mfu * 100, 2),
                "unit": "% of peak",
                "vs_baseline": round(result.mfu / NORTH_STAR_MFU, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
