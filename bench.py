#!/usr/bin/env python3
"""Driver benchmark entry point: ONE JSON line on stdout, no matter what.

North-star metric (BASELINE.md): Llama training MFU on the real chip, target
>=45%. The JSON line carries the train MFU as the primary value plus the
single-chip matmul MFU (BASELINE config #2) alongside, so both numbers are
driver-recorded.

Robustness (the round-1 postmortem): this parent process NEVER imports jax.
Each workload runs in a child process with a hard timeout — a wedged
tunneled backend is killed and retried with bounded backoff, and on final
failure the JSON line still appears with ``value: null`` and an ``error``.
All diagnostics go to stderr; stdout is exactly one parseable line.

Wedge budgeting (the round-3 postmortem: 963s spent learning "wedged"):
- A fast chip PROBE runs first (tiny matmul, short timeout). A confirmed
  dead probe skips every TPU workload — the run finishes in minutes with
  the chip-free control-plane metric still recorded.
- Every completed workload's JSON is appended to ``bench_partials.jsonl``
  immediately, so a mid-run wedge loses nothing already measured.
- Two consecutive all-attempts-timed-out workloads trigger a re-probe;
  if the chip is gone, remaining TPU workloads are skipped.

Journal fallback (the round-4 reality: the chip comes alive for ~15-minute
windows hours apart, and the driver's end-of-round bench run may land in a
wedge). ``tools/harvest.py`` journals every hardware measurement to
``harvest_results.jsonl`` the moment it lands. When a live workload here
fails (or the probe says wedge), the slot is filled from the freshest
journaled SAME-ROUND measurement (bounded age), clearly labeled in the
payload under ``journal`` with per-workload ages — the value is still a
real-hardware number from this round, just measured earlier in it.

Test knobs (env): ``BENCH_PROBE_TIMEOUT`` overrides the probe timeout;
``BENCH_TEST_FORCE_WEDGE=1`` makes the probe child hang (simulated wedge);
``BENCH_JOURNAL_PATH`` points the fallback at a different journal file.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
NORTH_STAR_TRAIN_MFU_PCT = 45.0  # BASELINE.md: >=45% train MFU north star

ATTEMPTS = 3
BACKOFF_SECONDS = 30.0
DEADLINE_SECONDS = 1500.0  # global budget; retries stop when exceeded
PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", "60"))
PARTIALS_PATH = os.path.join(REPO_ROOT, "bench_partials.jsonl")
JOURNAL_PATH = os.environ.get(
    "BENCH_JOURNAL_PATH", os.path.join(REPO_ROOT, "harvest_results.jsonl")
)
JOURNAL_MAX_AGE_SECONDS = 48 * 3600.0  # same-round bound for adopted entries

_T0 = time.monotonic()
_consecutive_timeouts = 0  # workloads whose every attempt timed out


def _log(msg: str) -> None:
    print(f"bench [{time.monotonic() - _T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


def _persist(workload: str, result: dict | None, note: str = "") -> None:
    """Append one workload outcome to the partials file as it completes —
    a mid-run wedge must not erase what was already measured."""
    rec = {"workload": workload, "t": round(time.monotonic() - _T0, 1)}
    if note:
        rec["note"] = note
    rec["result"] = result
    try:
        with open(PARTIALS_PATH, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError as e:  # diagnostics must never kill the run
        _log(f"partials write failed: {e}")


def _run_child(workload: str, timeout: float, platforms: str | None) -> dict:
    """One attempt: spawn the runner, parse its last JSON stdout line."""
    # cwd must be the repo root: the tunneled TPU backend fails to register
    # from other working directories. APPEND to PYTHONPATH — the TPU
    # backend's PJRT plugin registers via a sitecustomize dir already on it;
    # clobbering that path would cut every child off from the real chip.
    existing = os.environ.get("PYTHONPATH", "")
    env = {
        **os.environ,
        "PYTHONPATH": f"{REPO_ROOT}{os.pathsep}{existing}" if existing else REPO_ROOT,
    }
    if platforms is not None:
        env["JAX_PLATFORMS"] = platforms
    proc = subprocess.run(
        [sys.executable, "-m", "k8s_gpu_device_plugin_tpu.benchmark.runner", workload],
        cwd=REPO_ROOT,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    for line in proc.stderr.splitlines():
        _log(f"{workload}> {line}")
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                # child killed mid-print (wedge/OOM): a truncated line is
                # a failed attempt, not a reason to abort the whole run
                break
    raise RuntimeError(f"no JSON line from {workload} runner (rc={proc.returncode})")


def run_workload(
    workload: str,
    timeout: float,
    platforms: tuple[str | None, ...] = (None,),
    attempts: int = ATTEMPTS,
    backoff: float = BACKOFF_SECONDS,
) -> dict | None:
    """Up to ``attempts`` tries with backoff, all inside the global deadline.

    ``platforms`` cycles JAX_PLATFORMS values across attempts (None =
    inherit): the tunneled chip has been seen failing as the pinned backend
    name while still reachable under another ('axon' vs 'tpu' vs auto)."""
    global _consecutive_timeouts
    all_timed_out = True
    attempts_made = 0
    deadline_hit = False
    for attempt in range(1, attempts + 1):
        remaining = DEADLINE_SECONDS - (time.monotonic() - _T0)
        if remaining <= 5:
            _log(f"{workload}: global deadline exhausted before attempt {attempt}")
            deadline_hit = True
            break
        plat = platforms[(attempt - 1) % len(platforms)]
        _log(
            f"{workload}: attempt {attempt}/{attempts} "
            f"(timeout {timeout:.0f}s, JAX_PLATFORMS={'inherit' if plat is None else plat!r})"
        )
        attempts_made += 1
        try:
            result = _run_child(workload, timeout=min(timeout, remaining), platforms=plat)
        except subprocess.TimeoutExpired:
            _log(f"{workload}: attempt {attempt} timed out (backend wedged?)")
            result = None
        except Exception as e:  # noqa: BLE001 - diagnostics must not kill the line
            _log(f"{workload}: attempt {attempt} failed: {type(e).__name__}: {e}")
            result = None
            all_timed_out = False
        if result is not None and "error" not in result:
            _consecutive_timeouts = 0
            _persist(workload, result)
            return result
        if result is not None:
            _log(f"{workload}: runner error: {result['error']}")
            all_timed_out = False
        if attempt < attempts:
            _log(f"{workload}: backing off {backoff:.0f}s")
            time.sleep(backoff)
    # zero attempts (pure deadline exhaustion) is NOT evidence of a wedge —
    # don't let budget running out masquerade as a chip failure
    if attempts_made > 0 and all_timed_out:
        _consecutive_timeouts += 1
    note = (
        "deadline exhausted before any attempt"
        if attempts_made == 0 and deadline_hit
        else "all attempts failed"
    )
    _persist(workload, None, note=note)
    return None


def journal_row_ok(rec) -> bool:
    """One definition of 'this journal row landed': shared with
    tools/harvest.py's --resume so adoption and resume can never disagree
    on which rows count."""
    if not isinstance(rec, dict):
        return False
    result = rec.get("result")
    return isinstance(result, dict) and "error" not in result


def journal_row_fresh(rec, now: float | None = None) -> bool:
    """Row is recent enough to count (adoption AND --resume use this — a
    row only one of them honors would strand a slot: resume skips it as
    done while adoption drops it as stale). Requires an explicit ``ts``:
    a file-mtime fallback would refresh on every append, laundering
    prior-round rows as fresh."""
    try:
        ts = float(rec["ts"])
    except (KeyError, TypeError, ValueError):
        return False
    now = time.time() if now is None else now
    return now - ts <= JOURNAL_MAX_AGE_SECONDS


def _journal_results() -> dict[str, tuple[dict, float]]:
    """Latest successful hardware measurement per journal row, with its
    measurement unix time (rows journaled by ``tools/harvest.py`` carry a
    ``ts``; rows without one never qualify). Entries past
    JOURNAL_MAX_AGE_SECONDS are dropped — the fallback exists to surface
    THIS round's scarce-window measurements, not stale history."""
    out: dict[str, tuple[dict, float]] = {}
    try:
        with open(JOURNAL_PATH) as f:
            lines = f.readlines()
    except OSError:
        return out
    now = time.time()
    for line in lines:
        # any single bad line (truncated write, non-dict JSON, junk ts) is
        # skipped — the one-JSON-line-on-stdout contract outranks it
        try:
            rec = json.loads(line.strip())
            if not (journal_row_ok(rec) and journal_row_fresh(rec, now)):
                continue
            out[rec.get("workload", "")] = (rec["result"], float(rec["ts"]))
        except (ValueError, TypeError):
            continue
    return out


def _collect_artifacts(
    results: dict[str, dict | None],
) -> dict[str, dict[str, str]]:
    """Gather each workload's observability artifacts (the runner's
    Perfetto trace / cProfile paths) into ``bench_traces/`` next to the
    driver's ``BENCH_*.json`` history, and map workload -> relative
    paths for the payload. Missing/unreadable files are skipped — the
    artifacts are diagnostics, never a reason to fail the line."""
    import shutil

    dest_dir = os.path.join(REPO_ROOT, "bench_traces")
    out: dict[str, dict[str, str]] = {}
    for workload, result in results.items():
        if not isinstance(result, dict):
            continue
        entry: dict[str, str] = {}
        for key in ("trace_path", "profile_path"):
            src = result.get(key)
            if not isinstance(src, str) or not os.path.exists(src):
                continue
            dest = os.path.join(
                dest_dir, f"{workload}_{os.path.basename(src)}"
            )
            try:
                os.makedirs(dest_dir, exist_ok=True)
                if os.path.abspath(src) != os.path.abspath(dest):
                    shutil.copyfile(src, dest)
                entry[key] = os.path.relpath(dest, REPO_ROOT)
            except OSError as e:
                _log(f"artifact collect failed for {workload}: {e}")
        if entry:
            out[workload] = entry
    return out


def probe_chip(platforms: tuple[str | None, ...]) -> bool:
    """Fast up-front liveness check: a tiny matmul child with a short
    timeout. Round 3 spent 963s of a scarce hardware window discovering a
    wedge; this discovers it in ~PROBE_TIMEOUT seconds."""
    # max(2, len(platforms)) attempts (3 with the default tuple): the probe
    # gates the whole run, so it must try every JAX_PLATFORMS fallback the
    # real workloads would have tried. Worst-case wedge-mode budget:
    # attempts x PROBE_TIMEOUT + (attempts-1) x 5s backoff.
    result = run_workload(
        "probe", timeout=PROBE_TIMEOUT, platforms=platforms,
        attempts=max(2, len(platforms)), backoff=5.0,
    )
    return result is not None


def main() -> int:
    # fresh partials file per run (the file is this run's journal)
    try:
        open(PARTIALS_PATH, "w").close()
    except OSError:
        pass

    tpu_platforms = (None, "tpu", "")  # pinned name -> libtpu name -> auto
    chip_live = probe_chip(tpu_platforms)
    if not chip_live:
        _log("probe: chip unreachable — skipping all TPU workloads (wedge mode)")

    matmul = (
        run_workload("matmul", timeout=300, platforms=tpu_platforms)
        if chip_live
        else None
    )
    train = (
        run_workload("train", timeout=480, platforms=tpu_platforms) if matmul else None
    )
    roundtrip = run_workload("roundtrip", timeout=120)
    # BASELINE #2 exercised THROUGH the plugin (Allocate env contract ->
    # subprocess workload); diagnostic unless the direct path also worked
    allocated = (
        run_workload("allocated", timeout=480, platforms=tpu_platforms)
        if matmul and _chip_still_live(tpu_platforms)
        else None
    )

    # Secondary diagnostics, only with budget left after the primary
    # workloads (never risk the main metric): int8-matmul train throughput,
    # then serving-side decode throughput (bf16 and int8-weight variants).
    def secondary(workload: str, cap: float, gate, min_remaining: float):
        remaining = DEADLINE_SECONDS - (time.monotonic() - _T0)
        if not gate or remaining <= min_remaining:
            return None
        if not _chip_still_live(tpu_platforms):
            _log(f"{workload}: skipped — chip wedged mid-run")
            return None
        return run_workload(
            workload, timeout=min(cap, remaining - 20), platforms=tpu_platforms
        )

    # fused single-pass AdamW: numerics-identical to the optax chain, so
    # if it wins it can honestly carry the primary train metric
    train_fusedopt = secondary("train_fusedopt", 480, train, 220)
    train_int8 = secondary("train_int8", 480, train, 200)
    decode = secondary("decode", 420, train, 180)
    decode_int8w = secondary("decode_int8w", 420, decode, 180)
    decode_int4w = secondary("decode_int4w", 420, decode_int8w, 160)

    # host-side native-gather throughput: no chip involved, so it lands
    # even in wedge mode — but AFTER every chip-gated row, so a slow host
    # never spends live-window deadline budget while the chip idles
    dataload = run_workload("dataload", timeout=240, attempts=1)

    # Journal fallback: any slot the live run could not fill adopts the
    # freshest same-round hardware measurement from tools/harvest.py's
    # journal, labeled below with its age. "train_tuned" is the same train
    # workload re-timed after flash_tune persisted its winners (same model,
    # same objective), so it may carry the train slot when both exist.
    journal = _journal_results()
    adopted: dict[str, float] = {}

    def _adopt(live: dict | None, *rows: str) -> dict | None:
        if live is not None:
            return live
        for row in rows:
            hit = journal.get(row)
            if hit is not None:
                adopted[row] = hit[1]  # label the row actually matched
                return hit[0]
        return None

    matmul = _adopt(matmul, "matmul")
    train = _adopt(train, "train_tuned", "train")
    allocated = _adopt(allocated, "allocated")
    train_fusedopt = _adopt(train_fusedopt, "train_fusedopt")
    train_int8 = _adopt(train_int8, "train_int8")
    decode = _adopt(decode, "decode")
    decode_int8w = _adopt(decode_int8w, "decode_int8w")
    decode_int4w = _adopt(decode_int4w, "decode_int4w")

    extra: dict = {}
    artifacts = _collect_artifacts({
        "matmul": matmul, "train": train, "roundtrip": roundtrip,
        "allocated": allocated, "train_fusedopt": train_fusedopt,
        "train_int8": train_int8, "decode": decode,
        "decode_int8w": decode_int8w, "decode_int4w": decode_int4w,
        "dataload": dataload,
    })
    if artifacts:
        extra["artifacts"] = artifacts
    if adopted:
        extra["journal"] = {
            "path": os.path.relpath(JOURNAL_PATH, REPO_ROOT),
            "adopted_age_seconds": {
                w: round(time.time() - ts, 1) for w, ts in adopted.items()
            },
            "note": (
                "the live run could not measure these workloads; values are "
                "this round's live-hardware measurements journaled by "
                "tools/harvest.py"
            ),
        }
    if matmul:
        extra["matmul_bf16_mfu_pct"] = matmul["mfu_pct"]
        extra["matmul_tflops"] = matmul["tflops"]
        extra["device_kind"] = matmul.get("device_kind", "")
    if train:
        extra["train_tokens_per_second"] = train["tokens_per_second"]
        extra["train_step_ms"] = train["step_ms"]
        extra["train_model_dims"] = train.get("model")
        extra["train_opt_impl"] = "optax"
    if train_fusedopt:
        extra["train_fusedopt_mfu_pct"] = train_fusedopt["mfu_pct"]
        extra["train_fusedopt_step_ms"] = train_fusedopt["step_ms"]
        # Same model/objective/trajectory (test-pinned), so the fused
        # implementation may carry the primary — but only past a 2%
        # relative margin (two single measurements; a bare max() would
        # ratchet the headline upward on noise alone), and with the
        # optax run's numbers preserved alongside for the comparison.
        if train and train_fusedopt["mfu_pct"] > train["mfu_pct"] * 1.02:
            extra["train_optax_mfu_pct"] = train["mfu_pct"]
            extra["train_optax_step_ms"] = train["step_ms"]
            train = {**train, "mfu_pct": train_fusedopt["mfu_pct"],
                     "tokens_per_second": train_fusedopt["tokens_per_second"],
                     "step_ms": train_fusedopt["step_ms"]}
            extra["train_tokens_per_second"] = train["tokens_per_second"]
            extra["train_step_ms"] = train["step_ms"]
            extra["train_opt_impl"] = "fused"
    if roundtrip:
        extra["control_plane_allocs_per_second"] = roundtrip["allocs_per_second"]
    if dataload:
        extra["dataload_native_speedup"] = dataload["native_speedup"]
        extra["dataload_native_tokens_per_second"] = dataload[
            "native_tokens_per_second"
        ]
        extra["dataload_cache_state"] = dataload["cache_state"]
    if train_int8:
        extra["train_int8_mfu_pct"] = train_int8["mfu_pct"]
        extra["train_int8_tokens_per_second"] = train_int8["tokens_per_second"]
        # standard accounting: bf16 6N model FLOPs vs bf16 peak ("bf16-
        # equivalent throughput"); the int8 path can exceed 100 in principle
        extra["train_int8_accounting"] = "bf16_model_flops_vs_bf16_peak"
    if decode:
        extra["decode_tokens_per_second"] = decode["decode_tokens_per_second"]
        extra["decode_prefill_ms"] = decode["prefill_ms"]
        extra["decode_hbm_util_pct"] = decode["hbm_util_pct"]
        extra["decode_shape"] = decode["decode_shape"]
    if decode_int8w:
        extra["decode_int8w_tokens_per_second"] = decode_int8w[
            "decode_tokens_per_second"
        ]
        extra["decode_int8w_hbm_util_pct"] = decode_int8w["hbm_util_pct"]
    if decode_int4w:
        extra["decode_int4w_tokens_per_second"] = decode_int4w[
            "decode_tokens_per_second"
        ]
        extra["decode_int4w_hbm_util_pct"] = decode_int4w["hbm_util_pct"]
    if allocated:
        extra["allocated_matmul_mfu_pct"] = allocated["mfu_pct"]
        extra["allocated_matmul_n"] = allocated.get("n")
        extra["allocated_matmul_iters"] = allocated.get("iters")
        extra["allocated_via"] = (
            f"{allocated['backend_used']}:TPU_VISIBLE_CHIPS="
            f"{allocated['visible_chips']}"
        )

    if train:
        payload = {
            "metric": "llama_train_bf16_mfu",
            "value": train["mfu_pct"],
            "unit": "% of peak",
            "vs_baseline": round(train["mfu_pct"] / NORTH_STAR_TRAIN_MFU_PCT, 3),
            **extra,
        }
    elif matmul:
        # Train bench unavailable: report the matmul MFU under its own name
        # (no vs_baseline — the 45% north star is a TRAIN-MFU target and the
        # ratio would be apples-to-oranges).
        payload = {
            "metric": "matmul_bf16_mfu",
            "value": matmul["mfu_pct"],
            "unit": "% of peak",
            "vs_baseline": None,
            "error": "train bench failed; matmul-only result",
            **extra,
        }
    else:
        reason = (
            "TPU chip unreachable (fast probe failed; wedge mode, TPU "
            "workloads skipped)"
            if not chip_live
            else "TPU workloads failed after retries (see stderr diagnostics)"
        )
        payload = {
            "metric": "llama_train_bf16_mfu",
            "value": None,
            "unit": "% of peak",
            "vs_baseline": None,
            "error": reason,
            **extra,
        }

    if adopted:
        # value is real (journaled same-round hardware); the live failure
        # is still surfaced, under a name that can't read as a bad value.
        # Any adoption implies a live miss — probe failure, mid-run wedge,
        # gating off an earlier failure, or deadline exhaustion.
        reason = (
            "TPU chip unreachable at bench time (probe failed)"
            if not chip_live
            else f"live run could not measure {sorted(adopted)} "
            "(mid-run wedge, gating, or deadline)"
        )
        payload.setdefault(
            "live_error",
            f"{reason}; journaled same-round hardware values adopted",
        )

    print(json.dumps(payload))
    return 0


def _chip_still_live(tpu_platforms: tuple[str | None, ...]) -> bool:
    """Mid-run wedge detector: after two consecutive all-timeout workloads,
    re-probe once; a dead probe stops us burning the rest of the window."""
    global _consecutive_timeouts
    if _consecutive_timeouts < 2:
        return True
    _log("two consecutive workload timeouts — re-probing chip")
    # cycle every platform fallback: a name-specific transient must not
    # condemn the rest of the run (cheap next to the N-minute workload
    # timeouts this re-probe replaces)
    live = run_workload(
        "probe", timeout=PROBE_TIMEOUT, platforms=tpu_platforms,
        attempts=len(tpu_platforms), backoff=5.0,
    )
    if live is not None:
        _consecutive_timeouts = 0
        return True
    # leave the counter >= 2: every later _chip_still_live re-probes once,
    # cheap relative to the N-minute workload timeouts it replaces
    return False


if __name__ == "__main__":
    sys.exit(main())
